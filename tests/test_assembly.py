"""H2OAssembly munging pipelines (water/rapids/Assembly.java + h2o-py
h2o/assembly.py): fit/transform chains with frozen statistics and a
replayable artifact."""

import numpy as np
import pytest

from h2o3_tpu.assembly import (H2OAssembly, H2OBinaryOp, H2OColOp,
                               H2OColSelect, H2OScaler)
from h2o3_tpu.core.frame import Column, Frame


@pytest.fixture()
def fr(cl):
    rng = np.random.default_rng(0)
    f = Frame()
    f.add("a", Column.from_numpy(rng.uniform(1, 10, 500)))
    f.add("b", Column.from_numpy(rng.standard_normal(500) * 5 + 20))
    f.add("junk", Column.from_numpy(rng.standard_normal(500)))
    return f


class TestAssembly:
    def test_fit_transform_chain(self, fr):
        asm = H2OAssembly(steps=[
            ("select", H2OColSelect(["a", "b"])),
            ("log_a", H2OColOp("log", col="a", inplace=True)),
            ("scale", H2OScaler()),
            ("sum", H2OBinaryOp("+", "a", "b", new_col_name="ab")),
        ])
        out = asm.fit(fr)
        assert out.names == ["a", "b", "ab"]
        a = out.col("a").to_numpy()
        assert abs(a.mean()) < 1e-5 and abs(a.std() - 1) < 1e-4
        np.testing.assert_allclose(
            out.col("ab").to_numpy(),
            a + out.col("b").to_numpy(), atol=1e-5)

    def test_frozen_statistics_on_new_frame(self, fr, cl):
        """Scaler must reuse TRAINING stats at apply time."""
        asm = H2OAssembly(steps=[("scale", H2OScaler())])
        asm.fit(fr)
        shifted = Frame()
        for nm in fr.names:
            shifted.add(nm, Column.from_numpy(
                fr.col(nm).to_numpy() + 100.0))
        out = asm.transform(shifted)
        # +100 input shift survives (stats frozen, not refit)
        scaler = asm.steps[0][1]
        assert out.col("a").to_numpy().mean() == pytest.approx(
            100.0 / scaler.sds["a"], rel=1e-3)

    def test_transform_before_fit_raises(self, fr):
        with pytest.raises(RuntimeError, match="not fitted"):
            H2OAssembly(steps=[("s", H2OScaler())]).transform(fr)

    def test_artifact_roundtrip(self, fr, tmp_path):
        asm = H2OAssembly(steps=[
            ("select", H2OColSelect(["a"])),
            ("sqrt", H2OColOp("sqrt", col="a")),
        ])
        expect = asm.fit(fr).col("a").to_numpy()
        p = str(tmp_path / "asm.bin")
        asm.save(p)
        re = H2OAssembly.load(p)
        np.testing.assert_allclose(re.transform(fr).col("a").to_numpy(),
                                   expect, atol=1e-6)
        bad = tmp_path / "bad.bin"
        bad.write_bytes(b"nope")
        with pytest.raises(ValueError, match="not an assembly"):
            H2OAssembly.load(str(bad))

    def test_colop_new_column(self, fr):
        asm = H2OAssembly(steps=[
            ("cos", H2OColOp("cos", col="a", inplace=False,
                             new_col_name="cos_a")),
        ])
        out = asm.fit(fr)
        assert "cos_a" in out.names and "a" in out.names
        np.testing.assert_allclose(out.col("cos_a").to_numpy(),
                                   np.cos(fr.col("a").to_numpy()), atol=1e-5)

    def test_top_level_import(self, fr):
        import h2o3_tpu as h2o

        asm = h2o.H2OAssembly(steps=[("sel", H2OColSelect(["b"]))])
        assert asm.fit(fr).names == ["b"]


def test_callable_op_pickles_and_names_stably(cl, tmp_path):
    """jnp.cos (a non-picklable ufunc object) normalizes to its name at
    construction, so artifacts save and derived names are stable."""
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    fr2 = Frame()
    fr2.add("a", Column.from_numpy(rng.uniform(0, 3, 100)))
    asm = H2OAssembly(steps=[
        ("cos", H2OColOp(jnp.cos, col="a", inplace=False)),
    ])
    out = asm.fit(fr2)
    assert "cos_a" in out.names           # name from __name__, not repr
    p = str(tmp_path / "c.bin")
    asm.save(p)                           # must not raise PicklingError
    re = H2OAssembly.load(p)
    np.testing.assert_allclose(re.transform(fr2).col("cos_a").to_numpy(),
                               np.cos(fr2.col("a").to_numpy()), atol=1e-5)
    with pytest.raises(ValueError, match="unknown op"):
        H2OColOp(lambda x: x, col="a")
