"""DeepLearning tests (reference pyunits testdir_algos/deeplearning)."""

import numpy as np
import pytest

from h2o3_tpu.core.frame import Column, Frame, T_CAT


def _xor_data(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    fr = Frame()
    fr.add("x0", Column.from_numpy(X[:, 0]))
    fr.add("x1", Column.from_numpy(X[:, 1]))
    fr.add("y", Column.from_numpy(np.where(y == 1, "on", "off"), ctype=T_CAT))
    return fr


def test_dl_learns_xor(cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    fr = _xor_data()
    m = DeepLearning(hidden=[16, 16], epochs=60, seed=42,
                     mini_batch_size=64).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.auc > 0.97
    pred = m.predict(fr)
    assert set(pred.names) == {"predict", "off", "on"}


def test_dl_regression(cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    rng = np.random.default_rng(1)
    X = rng.normal(size=(3000, 3))
    y = np.sin(X[:, 0]) + X[:, 1] ** 2 + 0.1 * X[:, 2]
    fr = Frame.from_numpy(np.column_stack([X, y]), names=["a", "b", "c", "y"])
    m = DeepLearning(hidden=[32, 32], epochs=40, seed=0, activation="Tanh",
                     mini_batch_size=64).train(y="y", training_frame=fr)
    mm = m._output.training_metrics
    assert mm.r2 > 0.9
    vi = m.varimp()
    assert vi is not None and set(vi) == {"a", "b", "c"}


def test_dl_autoencoder_anomaly(cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    rng = np.random.default_rng(2)
    X = rng.normal(size=(2000, 4))
    X[:, 2] = X[:, 0] + 0.05 * rng.normal(size=2000)   # low-rank structure
    X[:, 3] = X[:, 1] - X[:, 0]
    fr = Frame.from_numpy(X, names=list("abcd"))
    m = DeepLearning(autoencoder=True, hidden=[2], epochs=40, seed=3,
                     activation="Tanh", mini_batch_size=64).train(training_frame=fr)
    # anomalous points reconstruct worse
    Xa = X.copy()
    Xa[:50] = rng.uniform(-6, 6, size=(50, 4))
    fra = Frame.from_numpy(Xa, names=list("abcd"))
    err = m.anomaly(fra).col("Reconstruction.MSE").to_numpy()
    assert err[:50].mean() > 3 * err[50:].mean()


def test_dl_sgd_momentum_path(cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    fr = _xor_data(n=1000, seed=5)
    m = DeepLearning(hidden=[16], epochs=40, seed=7, adaptive_rate=False,
                     rate=0.05, momentum_start=0.5, momentum_stable=0.9,
                     mini_batch_size=32).train(y="y", training_frame=fr)
    assert m._output.training_metrics.auc > 0.9


def test_dl_deepfeatures_shape(cl):
    from h2o3_tpu.models.deeplearning import DeepLearning

    fr = _xor_data(n=500, seed=6)
    m = DeepLearning(hidden=[8, 4], epochs=5, seed=1,
                     mini_batch_size=32).train(y="y", training_frame=fr)
    df = m.deepfeatures(fr, 1)
    assert df.ncols == 4 and df.nrows == 500


def test_autoencoder_metrics_and_versioned_save(cl, tmp_path):
    """ModelMetricsAutoEncoder (reconstruction MSE) + versioned artifact
    header (Iced/AutoBuffer analog)."""
    import numpy as np

    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.models.deeplearning import DeepLearning
    from h2o3_tpu.models.model import Model

    rng = np.random.default_rng(4)
    X = rng.standard_normal((400, 5))
    fr = Frame.from_numpy(X, names=[f"x{i}" for i in range(5)])
    m = DeepLearning(autoencoder=True, hidden=[3], epochs=3,
                     seed=1).train(training_frame=fr)
    mm = m._output.training_metrics
    assert mm is not None and np.isfinite(mm.mse) and mm.mse > 0
    assert "reconstruction" in mm.description
    # versioned save round-trip + foreign-file rejection
    p = str(tmp_path / "ae.bin")
    m.save(p)
    with open(p, "rb") as f:
        assert f.read(8) == b"H2O3TPUM"
    re = Model.load(p)
    assert float(re._output.training_metrics.mse) == float(mm.mse)
    bad = str(tmp_path / "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"garbage-not-a-model")
    import pytest

    with pytest.raises(ValueError, match="not an h2o3_tpu model"):
        Model.load(bad)
