"""Pure-numpy scoring engines for each MOJO payload family.

Numerics mirror the in-framework device scorers exactly:
- trees: h2o3_tpu/models/tree/compressed.py _traverse_fn (lockstep node
  walk, categorical split tables, per-feature NA bins) and binning.py
  bin_columns (searchsorted on training quantile edges);
- GLM:   h2o3_tpu/models/glm.py _glm_predict / _ordinal_class_probs;
- KMeans/DeepLearning: DataInfo.expand + their _predict_raw.
Reference counterparts: hex/genmodel/algos/tree/SharedTreeMojoModel.java:1,
glm/GlmMojoModel.java:1, kmeans/KMeansMojoModel.java:1,
deeplearning/DeeplearningMojoModel.java:1."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

NA_STRINGS = {"", "na", "nan", "null", "none", "n/a", "-"}


def _softmax(x: np.ndarray) -> np.ndarray:
    m = x - x.max(axis=-1, keepdims=True)
    e = np.exp(m)
    return e / e.sum(axis=-1, keepdims=True)


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.clip(x, -60, 60)))


def to_float(values) -> np.ndarray:
    """Raw column (strings / numbers / None) → float64 with NaN for NA."""
    a = np.asarray(values)
    if a.dtype.kind in "fiub":
        return a.astype(np.float64)
    out = np.full(a.shape, np.nan)
    flat = a.reshape(-1).astype(object)
    for i, v in enumerate(flat):
        if v is None:
            continue
        if isinstance(v, (int, float)):
            out.reshape(-1)[i] = float(v)
            continue
        s = str(v).strip()
        if s.lower() in NA_STRINGS:
            continue
        try:
            out.reshape(-1)[i] = float(s)
        except ValueError:
            pass
    return out


def to_codes(values, domain: Sequence[str]) -> np.ndarray:
    """Raw column → int32 domain codes; NA/unseen → -1 (the in-framework
    adapt_test contract: unseen test levels score as NA)."""
    lut = {str(d): i for i, d in enumerate(domain)}
    a = np.asarray(values).reshape(-1)
    out = np.full(a.shape, -1, np.int32)
    for i, v in enumerate(a):
        if v is None:
            continue
        s = str(v).strip()
        if s.lower() in NA_STRINGS:
            continue
        code = lut.get(s)
        if code is None:
            # numeric-looking categorical ("3.0" vs "3") — integral only;
            # "3.7" or "Infinity" must stay NA, not snap to a level
            try:
                fv = float(s)
                if fv == int(fv):
                    code = lut.get(str(int(fv)))
            except (ValueError, OverflowError):
                code = None
        out[i] = -1 if code is None else code
    return out


class ColumnBlock:
    """Named raw input columns; missing names resolve to all-NA."""

    def __init__(self, cols: Dict[str, Any], n: int):
        self.cols = cols
        self.n = n

    @staticmethod
    def from_dict(cols: Dict[str, Any]) -> "ColumnBlock":
        arrs = {k: np.asarray(v).reshape(-1) for k, v in cols.items()}
        lens = {len(v) for v in arrs.values()}
        if len(lens) > 1:
            detail = ", ".join(f"{k}={len(v)}" for k, v in arrs.items())
            raise ValueError(f"input columns have mismatched lengths: {detail}")
        return ColumnBlock(arrs, lens.pop() if lens else 0)

    def raw(self, name: str):
        return self.cols.get(name)


# ---------------------------------------------------------------------------
# tree family
# ---------------------------------------------------------------------------

class TreeScorer:
    """CompressedForest traversal + training-edge binning in numpy."""

    def __init__(self, bundle):
        s = bundle.scorer
        a = bundle.arrays
        meta = s["meta"]
        self.algo = s["algo"]
        self.category = str(s["model_category"])
        self.names: List[str] = list(meta["spec_names"])
        self.is_cat = a["spec_is_cat"].astype(bool)
        self.nbins = a["spec_nbins"].astype(np.int64)
        self.domains = {k: list(v) for k, v in (s.get("domains") or {}).items()}
        lens, flat = a["spec_edges_len"], a["spec_edges_flat"]
        self.edges, pos = [], 0
        for ln in lens:
            self.edges.append(np.asarray(flat[pos:pos + int(ln)], np.float64))
            pos += int(ln)
        self.feat = a["feat"].astype(np.int32)            # (T, M)
        self.thresh = a["thresh_bin"].astype(np.int32)
        self.na_left = a["na_left"].astype(bool)
        self.left = a["left"].astype(np.int32)
        self.right = a["right"].astype(np.int32)
        self.leaf_val = a["leaf_val"].astype(np.float64)
        self.leaf_val32 = a["leaf_val"].astype(np.float32)
        self.cat_split = a["cat_split"].astype(np.int32)
        self.cat_table = a["cat_table"].astype(bool)
        self.tree_class = a["tree_class"].astype(np.int32)
        self.na_bins = a["na_bins"].astype(np.int32)      # (F,)
        self.max_depth = int(meta["max_depth"])
        self.init_f = float(meta["init_f"])
        self.nclasses = int(meta["nclasses"])
        self.init_class = (np.asarray(a["init_class"], np.float64)
                           if "init_class" in a else None)
        self.init_class32 = (np.asarray(a["init_class"], np.float32)
                             if "init_class" in a else None)
        self.distribution = meta.get("distribution")
        self.cnorm = float(meta.get("cnorm", 1.0) or 1.0)

    @property
    def n_trees(self) -> int:
        return self.feat.shape[0]

    def bin(self, block: ColumnBlock) -> np.ndarray:
        """(N, F) int32 bin matrix, matching BinSpec.bin_columns."""
        n = block.n
        parts = []
        for i, name in enumerate(self.names):
            na_bin = int(self.nbins[i]) - 1
            raw = block.raw(name)
            if raw is None:
                parts.append(np.full(n, na_bin, np.int32))
                continue
            if self.is_cat[i]:
                codes = to_codes(raw, self.domains.get(name, []))
                b = np.where((codes < 0) | (codes >= na_bin), na_bin, codes)
            else:
                # float32 on both sides: the device binner compares f32
                # values to f32 edges, and values landing exactly on an
                # edge must fall in the same bin here
                x = to_float(raw).astype(np.float32)
                b = np.searchsorted(self.edges[i].astype(np.float32), x,
                                    side="left").astype(np.int32)
                b = np.where(np.isnan(x), na_bin, b)
            parts.append(b.astype(np.int32))
        return np.stack(parts, axis=-1)

    def margin(self, binned: np.ndarray) -> np.ndarray:
        """Σ leaf values over trees (+init) — (N,) or (N, K)."""
        N = binned.shape[0]
        T, _M = self.feat.shape
        tidx = np.arange(T)[None, :]                      # (1, T)
        node = np.zeros((N, T), np.int32)
        W = self.cat_table.shape[1] if self.cat_table.size else 1
        for _ in range(self.max_depth + 1):
            f = self.feat[tidx, node]                     # (N, T)
            leaf = f < 0
            fi = np.maximum(f, 0)
            b = np.take_along_axis(binned, fi, axis=1)    # (N, T)
            is_na = b == self.na_bins[fi]
            csid = self.cat_split[tidx, node]
            if self.cat_table.size:
                cat_left = self.cat_table[np.maximum(csid, 0),
                                          np.minimum(b, W - 1)]
            else:
                cat_left = np.zeros_like(leaf)
            go_left = np.where(csid >= 0, cat_left, b <= self.thresh[tidx, node])
            go_left = np.where(is_na, self.na_left[tidx, node], go_left)
            nxt = np.where(go_left, self.left[tidx, node],
                           self.right[tidx, node])
            node = np.where(leaf, node, nxt)
        # float32 SEQUENTIAL accumulation in tree order — bitwise-identical
        # to the device scan (compressed.py walk_one_tree), so margin-space
        # ties (e.g. the max-F1 labeling threshold, which IS a predicted
        # value) resolve the same way here as in the framework
        contrib = self.leaf_val32[tidx, node]             # (N, T) f32
        # per-class trees also occur at nclasses==2 (DRF
        # binomial_double_trees) — mirror compressed.py per_class_trees
        per_class = self.nclasses == 2 and T and self.tree_class.max() > 0
        K = self.nclasses if (self.nclasses > 2 or per_class) else 1
        if K > 1:
            acc = np.zeros((N, K), np.float32)
            for t in range(T):
                acc[:, self.tree_class[t]] += contrib[:, t]
        else:
            acc = np.zeros(N, np.float32)
            for t in range(T):
                acc += contrib[:, t]
        if self.init_class is not None:
            return acc + self.init_class32[None, :]
        return acc + np.float32(self.init_f)

    def _linkinv(self, f: np.ndarray) -> np.ndarray:
        # f32 in, f32 ops: matches the device Bernoulli.linkinv bit layout
        d = (self.distribution or "gaussian").lower()
        f = np.asarray(f, np.float32)
        if d in ("bernoulli", "quasibinomial"):
            one = np.float32(1.0)
            return one / (one + np.exp(-f))
        if d in ("poisson", "gamma", "tweedie", "multinomial"):
            return np.exp(np.clip(f, -60, 60))
        return f                      # gaussian/laplace/quantile/huber

    def raw_predict(self, block: ColumnBlock, chunk: int = 8192) -> Dict[str, np.ndarray]:
        outs = []
        binned = self.bin(block)
        for s in range(0, binned.shape[0], chunk):
            outs.append(self.margin(binned[s:s + chunk]))
        f = np.concatenate(outs, axis=0) if outs else self.margin(binned)
        if self.algo == "isolationforest":
            mean_len = f / self.n_trees
            score = np.exp2(-mean_len / max(self.cnorm, 1e-9))
            return {"score": score, "mean_length": mean_len}
        if self.algo == "drf":
            # vote means, not margins (DRFModel._predict_raw); the category
            # drives the branch — binomial forests carry nclasses=1
            if self.category == "Multinomial" or f.ndim == 2:
                p = np.clip(f, 0.0, 1.0)
                p = p / np.maximum(p.sum(axis=-1, keepdims=True), 1e-12)
                return {"probs": p}
            if self.category == "Binomial":
                p = np.clip(f, 0.0, 1.0)
                return {"probs": np.stack([1 - p, p], axis=-1)}
            return {"value": f}
        if self.category == "Multinomial":
            return {"probs": _softmax(f)}
        if self.category == "Binomial":
            p = self._linkinv(f)
            return {"probs": np.stack([1 - p, p], axis=-1)}
        return {"value": self._linkinv(f)}


# ---------------------------------------------------------------------------
# DataInfo expansion (shared by GLM / KMeans / DeepLearning)
# ---------------------------------------------------------------------------

class DataInfoExpander:
    """numpy twin of h2o3_tpu/models/data_info.py DataInfo.expand."""

    def __init__(self, state: dict):
        self.cat_names = list(state["cat_names"])
        self.num_names = list(state["num_names"])
        self.domains = {k: list(v) for k, v in state["domains"].items()}
        self.cards = [int(c) for c in state["cards"]]
        self.standardize = bool(state["standardize"])
        self.use_all_factor_levels = bool(state["use_all_factor_levels"])
        self.num_means = np.asarray(state["num_means"], np.float64)
        self.num_sigmas = np.asarray(state["num_sigmas"], np.float64)
        self.cat_modes = np.asarray(state["cat_modes"], np.int32)
        self.impute_values = np.asarray(state["impute_values"], np.float64)

    def expand(self, block: ColumnBlock) -> np.ndarray:
        n = block.n
        base = 0 if self.use_all_factor_levels else 1
        parts = []
        for i, name in enumerate(self.cat_names):
            raw = block.raw(name)
            codes = (to_codes(raw, self.domains.get(name, []))
                     if raw is not None else np.full(n, -1, np.int32))
            card = max(self.cards[i], base + 1)
            mode = int(self.cat_modes[i]) if self.cat_modes.size > i else 0
            codes = np.where((codes < 0) | (codes >= card), mode, codes)
            oh = np.eye(card)[codes]
            parts.append(oh[:, base:] if base else oh)
        if self.num_names:
            nums = np.stack(
                [to_float(block.raw(nm)) if block.raw(nm) is not None
                 else np.full(n, np.nan) for nm in self.num_names], axis=-1)
            nums = np.where(np.isnan(nums), self.impute_values[None, :], nums)
            if self.standardize:
                nums = (nums - self.num_means[None, :]) / self.num_sigmas[None, :]
            parts.append(nums)
        if not parts:
            raise ValueError("no predictors")
        return np.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]


class GlmScorer:
    def __init__(self, bundle):
        s = bundle.scorer
        meta = s["meta"]
        self.beta = np.asarray(bundle.arrays["beta"], np.float64)
        self.linkname = meta["linkname"]
        self.link_power = float(meta["link_power"])
        self.di = DataInfoExpander(meta["dinfo"])
        dom = s.get("response_domain") or []
        self.nclasses = len(dom) if dom else 1

    def _linkinv(self, eta: np.ndarray) -> np.ndarray:
        nm, lp = self.linkname, self.link_power
        if nm == "identity":
            return eta
        if nm == "log":
            return np.exp(np.clip(eta, -30, 30))
        if nm == "logit":
            return _sigmoid(eta)
        if nm == "inverse":
            return 1.0 / np.where(np.abs(eta) < 1e-10, 1e-10, eta)
        if nm == "tweedie":
            if lp == 0.0:
                return np.exp(np.clip(eta, -30, 30))
            return np.maximum(eta, 1e-10) ** (1.0 / lp)
        raise ValueError(f"unknown link {nm!r}")

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        X = self.di.expand(block)
        if self.linkname == "ordinal":
            p = X.shape[1]
            beta, traw = self.beta[:p], self.beta[p:]
            th = traw[0] + np.concatenate(
                [np.zeros(1), np.cumsum(np.logaddexp(0.0, traw[1:]))])
            eta = X @ beta
            cum = _sigmoid(th[None, :] - eta[:, None])
            n = X.shape[0]
            cf = np.concatenate([np.zeros((n, 1)), cum, np.ones((n, 1))], 1)
            return {"probs": np.maximum(cf[:, 1:] - cf[:, :-1], 0.0)}
        Xi = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        if self.nclasses > 2:
            return {"probs": _softmax(Xi @ self.beta)}
        mu = self._linkinv(Xi @ self.beta)
        if self.nclasses == 2:
            return {"probs": np.stack([1 - mu, mu], axis=-1)}
        return {"value": mu}


class KMeansScorer:
    def __init__(self, bundle):
        self.centers = np.asarray(bundle.arrays["centers"], np.float64)
        self.di = DataInfoExpander(bundle.scorer["meta"]["dinfo"])

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        X = self.di.expand(block)
        d2 = ((X * X).sum(axis=1, keepdims=True)
              - 2.0 * X @ self.centers.T
              + (self.centers * self.centers).sum(axis=1)[None, :])
        return {"cluster": np.argmin(d2, axis=1).astype(np.int32),
                "dist2": d2.min(axis=1)}


class DeepLearningScorer:
    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        a = bundle.arrays
        self.layers = [(np.asarray(a[f"W{i}"], np.float64),
                        np.asarray(a[f"b{i}"], np.float64))
                       for i in range(int(meta["n_layers"]))]
        self.activation = meta["activation"]
        self.nclasses = int(meta["nclasses"])
        self.autoencoder = bool(meta["autoencoder"])
        self.di = DataInfoExpander(meta["dinfo"])

    def _act(self, x: np.ndarray) -> np.ndarray:
        base = self.activation.replace("withdropout", "")
        if base == "tanh":
            return np.tanh(x)
        if base == "rectifier":
            return np.maximum(x, 0.0)
        if base == "maxout":
            return np.maximum(x, 0.5 * x)
        raise ValueError(f"unknown activation {self.activation!r}")

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        X = self.di.expand(block)
        h = X
        for W, b in self.layers[:-1]:
            h = self._act(h @ W + b)
        W, b = self.layers[-1]
        out = h @ W + b
        if self.autoencoder:
            err = np.mean((out - X) ** 2, axis=-1)
            return {"reconstruction": out, "score": err, "value": err}
        if self.nclasses > 1:
            return {"probs": _softmax(out)}
        return {"value": out[:, 0]}


class PcaScorer:
    """hex/genmodel/algos/pca/PcaMojoModel: project the expanded row onto
    the eigenvector basis → PC1..PCk columns."""

    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        self.V = np.asarray(bundle.arrays["eigenvectors"], np.float64)
        self.k = int(meta["k"])
        self.di = DataInfoExpander(meta["dinfo"])

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        scores = self.di.expand(block) @ self.V
        return {"scores": scores, "value": scores[:, 0]}


def _np_loss_grad(name: str, period: float = 1.0):
    """numpy twin of glrm._loss_grad: dloss/du(a, u)."""
    if name == "quadratic":
        return lambda a, u: 2.0 * (u - a)
    if name == "absolute":
        return lambda a, u: np.sign(u - a)
    if name == "huber":
        return lambda a, u: np.clip(u - a, -1.0, 1.0)
    if name == "poisson":
        return lambda a, u: np.exp(u) - a
    if name == "logistic":
        return lambda a, u: -(2 * a - 1) / (1.0 + np.exp((2 * a - 1) * u))
    if name == "hinge":
        return lambda a, u: np.where((2 * a - 1) * u < 1.0, -(2 * a - 1), 0.0)
    if name == "periodic":
        w = 2.0 * np.pi / max(float(period), 1e-12)
        return lambda a, u: -w * np.sin((a - u) * w)
    if name == "categorical":
        return lambda a, u: (-2.0 * (2 * a - 1)
                             * np.maximum(1.0 - (2 * a - 1) * u, 0.0))
    raise ValueError(f"unknown GLRM loss {name!r}")


class GlrmScorer:
    """hex/genmodel/algos/glrm/GlrmMojoModel: iterative fixed-Y X solve
    (proximal gradient over the EXPORTED loss grid — per-column losses and
    the categorical multi-loss, matching the server's _composite_loss) then
    reconstruction X @ Y."""

    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        self.Y = np.asarray(bundle.arrays["archetypes"], np.float64)
        self.k = int(meta["k"])
        self.gamma_x = float(meta.get("gamma_x") or 0.0)
        self.reg_x = str(meta.get("regularization_x") or "None").lower()
        self.di = DataInfoExpander(meta["dinfo"])
        # per-expanded-column loss masks (glrm._composite_loss layout:
        # cat one-hot blocks first, then numerics)
        default = str(meta.get("loss") or "Quadratic").lower()
        multi = str(meta.get("multi_loss") or "Categorical").lower()
        period = float(meta.get("period") or 1.0)
        overrides = {}
        by_col = [str(x).lower() for x in (meta.get("loss_by_col") or [])]
        by_idx = [int(i) for i in (meta.get("loss_by_col_idx") or [])]
        names = list(meta.get("names") or
                     (self.di.cat_names + self.di.num_names))
        for i, nm in zip(by_idx, by_col):
            if i < len(names):
                overrides[names[i]] = nm
        col_loss = []
        for i, cn in enumerate(self.di.cat_names):
            col_loss.extend([overrides.get(cn, multi)]
                            * int(self.di.cards[i]))
        for nn in self.di.num_names:
            col_loss.append(overrides.get(nn, default))
        groups: Dict[str, list] = {}
        for ci, nm in enumerate(col_loss):
            groups.setdefault(nm, []).append(ci)
        self._terms = []
        pdim = self.Y.shape[1]
        for nm, cols in groups.items():
            mask = np.zeros(pdim)
            mask[[c for c in cols if c < pdim]] = 1.0
            self._terms.append((mask[None, :], _np_loss_grad(nm, period)))

    def _dloss(self, A: np.ndarray, U: np.ndarray) -> np.ndarray:
        return sum(m * g(A, U) for m, g in self._terms)

    def _prox(self, X: np.ndarray, step: float) -> np.ndarray:
        g = self.gamma_x * step
        if self.reg_x == "l1":
            return np.sign(X) * np.maximum(np.abs(X) - g, 0.0)
        if self.reg_x in ("l2", "quadratic"):
            return X / (1.0 + 2.0 * g)
        if self.reg_x == "nonnegative":
            return np.maximum(X, 0.0)
        if self.reg_x == "onesparse":
            keep = np.argmax(np.abs(X), axis=-1, keepdims=True)
            mask = np.arange(X.shape[-1])[None, :] == keep
            return np.where(mask, np.maximum(X, 0.0), 0.0)
        if self.reg_x == "unitonesparse":
            keep = np.argmax(np.abs(X), axis=-1, keepdims=True)
            return (np.arange(X.shape[-1])[None, :] == keep).astype(X.dtype)
        if self.reg_x == "simplex":
            u = np.sort(X, axis=-1)[:, ::-1]
            css = np.cumsum(u, axis=-1) - 1.0
            ind = np.arange(1, X.shape[-1] + 1, dtype=X.dtype)
            rho = np.sum(u - css / ind > 0, axis=-1, keepdims=True)
            theta = np.take_along_axis(css, rho - 1, axis=-1) / rho
            return np.maximum(X - theta, 0.0)
        return X

    def raw_predict(self, block: ColumnBlock,
                    iters: int = 30) -> Dict[str, np.ndarray]:
        A = self.di.expand(block)
        Y = self.Y
        X = np.zeros((A.shape[0], Y.shape[0]))
        step = 1.0 / (np.linalg.norm(Y) ** 2 + 1e-6)
        for _ in range(iters):
            G = self._dloss(A, X @ Y) @ Y.T
            X = self._prox(X - step * G, step)
        recon = X @ Y
        return {"reconstruction": recon, "x": X, "value": recon[:, 0]}


class Word2VecScorer:
    """hex/genmodel/algos/word2vec/Word2VecMojoModel: word → embedding."""

    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        self.vectors = np.asarray(bundle.arrays["vectors"], np.float64)
        self.vocab = {w: i for i, w in enumerate(meta["words"])}

    def word_vec(self, word: str):
        i = self.vocab.get(word)
        return self.vectors[i] if i is not None else None

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        name = next(iter(block.cols))
        raw = block.raw(name)
        dim = self.vectors.shape[1]
        out = np.full((block.n, dim), np.nan)
        for r, w in enumerate(np.asarray(raw, object)):
            i = self.vocab.get(str(w))
            if i is not None:
                out[r] = self.vectors[i]
        return {"vectors": out, "value": out[:, 0]}


class EnsembleScorer:
    """hex/genmodel/algos/ensemble/StackedEnsembleMojoModel: score nested
    base-model MOJOs, assemble the level-one block with the SAME column
    naming the trainer used, feed the metalearner MOJO."""

    def __init__(self, bundle):
        from h2o3_genmodel.reader import read_mojo_bundle

        meta = bundle.scorer["meta"]
        self.base_names = list(meta["base_names"])
        self.bases = []
        for i, name in enumerate(self.base_names):
            sub = read_mojo_bundle(bundle.arrays[f"base{i}"].tobytes())
            self.bases.append((name, sub.scorer, build_scorer(sub)))
        meta_bundle = read_mojo_bundle(bundle.arrays["metalearner"].tobytes())
        self.meta_scorer = build_scorer(meta_bundle)
        self.meta_names = list(meta_bundle.scorer["names"])

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        lone: Dict[str, np.ndarray] = {}
        for name, scorer_json, scorer in self.bases:
            raw = scorer.raw_predict(block)
            if "probs" in raw:
                probs = np.asarray(raw["probs"])
                if probs.shape[1] == 2:
                    lone[name] = probs[:, 1]
                else:
                    for j in range(probs.shape[1]):
                        lone[f"{name}_p{j}"] = probs[:, j]
            else:
                lone[name] = np.asarray(raw["value"])
        return self.meta_scorer.raw_predict(ColumnBlock.from_dict(lone))


class TargetEncoderScorer:
    """hex/genmodel/algos/targetencoder/TargetEncoderMojoModel: per-level
    posterior mean with optional blending; unseen/NA → prior."""

    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        a = bundle.arrays
        self.prior = float(meta["prior"])
        self.blending = bool(meta["blending"])
        self.k = float(meta["inflection_point"])
        self.f = float(meta["smoothing"])
        self.cols = []
        for i, centry in enumerate(meta["columns"]):
            self.cols.append((centry["name"], list(centry["domain"]),
                              np.asarray(a[f"num{i}"], np.float64),
                              np.asarray(a[f"den{i}"], np.float64)))

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for name, domain, num, den in self.cols:
            raw = block.raw(name)
            codes = (to_codes(raw, domain) if raw is not None
                     else np.full(block.n, -1, np.int32))
            safe = np.clip(codes, 0, max(len(domain) - 1, 0))
            n = den[safe]
            post = np.where(n > 0, num[safe] / np.maximum(n, 1e-12),
                            self.prior)
            if self.blending:
                lam = 1.0 / (1.0 + np.exp((self.k - n) / max(self.f, 1e-12)))
                post = np.where(n > 0, lam * post + (1 - lam) * self.prior,
                                self.prior)
            out[f"{name}_te"] = np.where(codes >= 0, post, self.prior)
        first = next(iter(out.values()))
        return {"te": out, "value": first}


class CoxPHScorer:
    """hex/genmodel/algos/coxph/CoxPHMojoModel: centered linear predictor
    (partial-hazard log-ratio) over the expanded row."""

    def __init__(self, bundle):
        meta = bundle.scorer["meta"]
        self.beta = np.asarray(bundle.arrays["beta"], np.float64)
        self.di = DataInfoExpander(meta["dinfo"])

    def raw_predict(self, block: ColumnBlock) -> Dict[str, np.ndarray]:
        lp = self.di.expand(block) @ self.beta
        return {"value": lp}


_TREE_ALGOS = {"gbm", "drf", "isolationforest", "xgboost"}


def build_scorer(bundle):
    algo = bundle.algo
    if algo in _TREE_ALGOS:
        return TreeScorer(bundle)
    if algo == "glm":
        return GlmScorer(bundle)
    if algo == "kmeans":
        return KMeansScorer(bundle)
    if algo == "deeplearning":
        return DeepLearningScorer(bundle)
    if algo == "pca":
        return PcaScorer(bundle)
    if algo == "glrm":
        return GlrmScorer(bundle)
    if algo == "word2vec":
        return Word2VecScorer(bundle)
    if algo == "stackedensemble":
        return EnsembleScorer(bundle)
    if algo == "targetencoder":
        return TargetEncoderScorer(bundle)
    if algo == "coxph":
        return CoxPHScorer(bundle)
    raise ValueError(f"h2o3_genmodel cannot score algo {algo!r}")
