"""MOJO zip container reader (hex/genmodel/MojoReaderBackend analog).

Parses the h2o3_tpu MOJO layout: `model.ini` ([info]/[columns]/[domains]),
`domains/d*.txt`, `scorer.json` and `data/*.npy` numpy payloads. Pure
stdlib + numpy."""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import numpy as np


class MojoBundle:
    """Raw parsed artifact: .info (model.ini [info] keys), .scorer
    (scorer.json), .arrays (data/*.npy)."""

    def __init__(self, info: Dict[str, str], scorer: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]):
        self.info = info
        self.scorer = scorer
        self.arrays = arrays

    @property
    def algo(self) -> str:
        return self.scorer["algo"]


def read_mojo_bundle(source) -> MojoBundle:
    """source: path / bytes / file-like of a MOJO zip."""
    if isinstance(source, (bytes, bytearray)):
        source = io.BytesIO(source)
    with zipfile.ZipFile(source) as z:
        names = set(z.namelist())
        if "scorer.json" not in names:
            raise ValueError(
                "not an h2o3_tpu MOJO: scorer.json missing (reference-Java "
                "MOJO payloads are not supported by this runtime)")
        scorer = json.loads(z.read("scorer.json").decode())
        info: Dict[str, str] = {}
        if "model.ini" in names:
            section = ""
            for ln in z.read("model.ini").decode().splitlines():
                ln = ln.strip()
                if ln.startswith("["):
                    section = ln
                elif section == "[info]" and " = " in ln:
                    k, _, v = ln.partition(" = ")
                    info[k.strip()] = v.strip()
        arrays = {}
        for n in names:
            if n.startswith("data/") and n.endswith(".npy"):
                arrays[n[len("data/"):-len(".npy")]] = np.load(
                    io.BytesIO(z.read(n)), allow_pickle=False)
    return MojoBundle(info, scorer, arrays)
