"""Batch-score a CSV with an AOT artifact, no framework install.

The AOT-lineage counterpart of predict_csv.py (PredictCsv.java analog):

    python -m h2o3_genmodel.aot_predict --artifact model_artifact/ \
        --input in.csv --output out.csv [--raw-npz raw.npz]

``--raw-npz`` additionally dumps the raw outputs (margins + probs/value)
as an npz — the bitwise-identity handle the round-trip tests compare
against in-process serving.
"""

from __future__ import annotations

import argparse
import csv
import sys

import numpy as np

from h2o3_genmodel.aot import load_artifact
from h2o3_genmodel.predict_csv import read_csv_columns


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="h2o3_genmodel.aot_predict",
        description="Score a CSV with an h2o3_tpu AOT artifact "
                    "(standalone runner).")
    ap.add_argument("--artifact", required=True,
                    help="artifact directory (manifest.json + payloads)")
    ap.add_argument("--input", required=True, help="input CSV (headered)")
    ap.add_argument("--output", help="output CSV (default: stdout)")
    ap.add_argument("--separator", default=",", help="field separator")
    ap.add_argument("--raw-npz",
                    help="also write raw margins/probs to this npz")
    args = ap.parse_args(argv)

    scorer = load_artifact(args.artifact)
    cols = read_csv_columns(args.input, args.separator)
    # one feature pack, one fused dispatch, however many outputs
    margins = scorer.margins(scorer.pack_features(cols))
    raw = scorer.raw_from_margins(margins)
    if args.raw_npz:
        np.savez(args.raw_npz, margins=margins, **raw)
    out = scorer.score(cols, raw=raw)

    names = list(out)
    n = len(np.asarray(out[names[0]]).reshape(-1))
    sink = open(args.output, "w", newline="") if args.output else sys.stdout
    try:
        w = csv.writer(sink)
        w.writerow(names)
        mats = [np.asarray(out[nm]).reshape(-1) for nm in names]
        for i in range(n):
            w.writerow([mats[j][i] for j in range(len(names))])
    finally:
        if args.output:
            sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
