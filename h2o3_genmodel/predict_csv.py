"""PredictCsv — batch-score a CSV with a MOJO, no framework install.

Reference: hex/genmodel/tools/PredictCsv.java:1 (the `java -cp h2o-genmodel
.jar hex.genmodel.tools.PredictCsv` entry point). Same contract: reads a
headered CSV, writes a CSV with `predict` (+ per-class probability columns
for classifiers).

    python -m h2o3_genmodel.predict_csv --mojo model.zip \
        --input in.csv --output out.csv [--separator ,]
"""

from __future__ import annotations

import argparse
import csv
import sys
from typing import Dict, List

import numpy as np

from h2o3_genmodel.easy import load_mojo


def read_csv_columns(path: str, sep: str = ",") -> Dict[str, List[str]]:
    with open(path, newline="") as f:
        rd = csv.reader(f, delimiter=sep)
        try:
            header = next(rd)
        except StopIteration:
            raise ValueError(f"{path}: empty file")
        cols: Dict[str, List[str]] = {h.strip().strip('"'): [] for h in header}
        keys = list(cols)
        for row in rd:
            for i, k in enumerate(keys):
                cols[k].append(row[i] if i < len(row) else "")
    return cols


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="h2o3_genmodel.predict_csv",
        description="Score a CSV file with an h2o3_tpu MOJO (numpy-only).")
    ap.add_argument("--mojo", required=True, help="path to the MOJO zip")
    ap.add_argument("--input", required=True, help="input CSV (headered)")
    ap.add_argument("--output", help="output CSV (default: stdout)")
    ap.add_argument("--separator", default=",", help="field separator")
    args = ap.parse_args(argv)

    model = load_mojo(args.mojo)
    cols = read_csv_columns(args.input, args.separator)
    out = model.score(cols)

    names = list(out)
    n = len(np.asarray(out[names[0]]).reshape(-1))
    sink = open(args.output, "w", newline="") if args.output else sys.stdout
    try:
        w = csv.writer(sink)
        w.writerow(names)
        mats = [np.asarray(out[nm]).reshape(-1) for nm in names]
        for i in range(n):
            w.writerow([mats[j][i] for j in range(len(names))])
    finally:
        if args.output:
            sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
