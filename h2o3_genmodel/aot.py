"""Standalone AOT-artifact scoring runtime (the genmodel side).

Loads an artifact directory exported by ``h2o3_tpu.artifact`` and scores
CSV / column input **without importing the training stack**: the only
dependencies are numpy, the standard library, and jax (to execute the
shipped program). Mirrors the MOJO runtime's charter (reader.py/easy.py)
for the AOT lineage.

Scoring path, in fallback order per row bucket:

1. deserialize the shipped AOT executable (``exec_b{N}.bin``) when its
   backend fingerprint matches this process — zero compilation, the
   cold-start-optimal path;
2. compile the shipped StableHLO text (``hlo_b{N}.mlir``) through the
   local XLA client — one compile of the *identical* program the exporter
   lowered, so predictions stay bitwise-identical to in-process serving.

Executable blobs pass through a restricted unpickler (bytes + jax
PyTreeDefs only) and every payload file is sha256-gated by the manifest
before any of its bytes are interpreted.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
from typing import Any, Dict, List, Optional

import numpy as np

_FORMAT = "h2o3-tpu-aot-artifact"
_FORMAT_VERSION = 1
_BLOB_VERSION = 1


class ArtifactError(ValueError):
    """Malformed / tampered / incompatible artifact."""


# ---------------------------------------------------------------------------
# manifest + payload reading (standalone twin of h2o3_tpu.artifact.manifest;
# tests/test_consistency.py pins the two formats together)
# ---------------------------------------------------------------------------

def _read_manifest(art_dir: str) -> Dict[str, Any]:
    path = os.path.join(art_dir, "manifest.json")
    try:
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
    except (OSError, ValueError) as e:
        raise ArtifactError(f"no readable manifest in {art_dir!r}: {e}") \
            from None
    if not isinstance(m, dict) or m.get("format") != _FORMAT:
        raise ArtifactError(f"not an {_FORMAT} artifact")
    ver = m.get("format_version")
    if not isinstance(ver, int) or not 1 <= ver <= _FORMAT_VERSION:
        raise ArtifactError(
            f"artifact format_version {ver!r} unsupported by this runtime "
            f"(supports 1..{_FORMAT_VERSION})")
    for key in ("model_category", "names", "files", "buckets", "post",
                "max_depth", "nclasses", "init_f", "model_checksum"):
        if key not in m:
            raise ArtifactError(f"manifest missing required key {key!r}")
    return m


def _read_payload(art_dir: str, entry: Dict[str, Any]) -> bytes:
    name = str(entry.get("name") or "")
    if not name or os.path.basename(name) != name or name.startswith("."):
        raise ArtifactError(f"illegal payload file name {name!r}")
    try:
        with open(os.path.join(art_dir, name), "rb") as f:
            data = f.read()
    except OSError as e:
        raise ArtifactError(f"payload {name!r} unreadable: {e}") from None
    if hashlib.sha256(data).hexdigest() != entry.get("sha256"):
        raise ArtifactError(f"payload {name!r} checksum mismatch — "
                            "artifact is corrupt or was tampered with")
    return data


class _ExecBlobUnpickler(pickle.Unpickler):
    _PREFIXES = ("jax.", "jaxlib.", "numpy.")
    _MODULES = {"jax", "jaxlib", "numpy"}

    def find_class(self, module, name):
        if module in self._MODULES or \
                any(module.startswith(p) for p in self._PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"executable blob references disallowed type {module}.{name}")


def _backend_fingerprint() -> str:
    import jax

    d = jax.devices()[0]
    return ";".join(["jax=" + jax.__version__,
                     "platform=" + str(d.platform),
                     "kind=" + str(getattr(d, "device_kind", "?")),
                     "devices=1"])


# ---------------------------------------------------------------------------
# the scorer
# ---------------------------------------------------------------------------

class AotScorer:
    """One loaded artifact: packed constants on device + one executable
    per row bucket, resolved lazily (deserialize -> StableHLO compile)."""

    def __init__(self, art_dir: str):
        self.dir = str(art_dir)
        m = _read_manifest(self.dir)
        self.manifest = m
        self.model_type: str = str(m.get("model_type") or "forest")
        if self.model_type not in ("forest", "glm", "pipeline"):
            raise ArtifactError(f"unsupported artifact model_type "
                                f"{self.model_type!r}")
        self.names: List[str] = list(m["names"])
        self.category: str = str(m["model_category"])
        self.response_domain: List[str] = list(m.get("response_domain")
                                               or [])
        self.default_threshold = float(m.get("default_threshold", 0.5))
        self.post: Dict[str, Any] = dict(m["post"])
        self.buckets: List[int] = sorted(int(b) for b in m["buckets"])
        self.nclasses = int(m["nclasses"])
        self.per_class = bool(m.get("per_class_trees"))

        if self.model_type == "pipeline":
            # the munge→score program ships with every constant (feature
            # plan consts + model tables) baked in; the manifest's
            # `pipeline` block and plan payload are the human-readable
            # record of WHAT was fused, verified here but not interpreted
            p = m.get("pipeline")
            if not isinstance(p, dict):
                raise ArtifactError("pipeline artifact manifest missing "
                                    "its 'pipeline' block")
            self.pipeline: Dict[str, Any] = dict(p)
            if "pipeline" not in m["files"]:
                raise ArtifactError("pipeline artifact manifest names no "
                                    "'pipeline' payload file")
            _read_payload(self.dir, m["files"]["pipeline"])
            self._arrays: Dict[str, np.ndarray] = {}
            self.domains: Dict[str, List[str]] = {
                k: list(v) for k, v in (m.get("domains") or {}).items()}
            self._dev: Optional[tuple] = None
            self._exec: Dict[int, Any] = {}
            self._post_jit = None
            self.loaded_from: Dict[int, str] = {}
            return
        payload = m["files"]["glm" if self.model_type == "glm"
                             else "forest"]
        with np.load(io.BytesIO(_read_payload(self.dir, payload)),
                     allow_pickle=False) as z:
            arrays = {k: np.asarray(z[k]) for k in z.files}
        self._arrays = arrays
        F = len(self.names)
        if self.model_type == "glm":
            g = m.get("glm")
            if not isinstance(g, dict):
                raise ArtifactError("glm artifact manifest missing its "
                                    "'glm' configuration block")
            self.glm: Dict[str, Any] = dict(g)
            if int(g.get("n_cat", 0)) + int(g.get("n_num", 0)) != F:
                raise ArtifactError("glm layout disagrees with manifest "
                                    "names")
        else:
            if int(arrays["spec_is_cat"].shape[0]) != F:
                raise ArtifactError("packed spec width disagrees with "
                                    "manifest names")
            self.is_cat = arrays["spec_is_cat"].astype(bool)
        self.domains: Dict[str, List[str]] = {
            k: list(v) for k, v in (m.get("domains") or {}).items()}
        # device-side constants are materialized on first use (load() stays
        # import-cheap for cold-start measurement)
        self._dev: Optional[tuple] = None
        self._exec: Dict[int, Any] = {}
        self._post_jit = None                     # cached fused post program
        self.loaded_from: Dict[int, str] = {}     # bucket -> "exec"|"hlo"

    # -- device constants -------------------------------------------------
    def _device_args(self) -> tuple:
        if self._dev is not None:
            return self._dev
        import jax.numpy as jnp

        a = self._arrays
        if self.model_type == "pipeline":
            self._dev = ()           # everything is baked into the program
            return self._dev
        if self.model_type == "glm":
            # the GLM program bakes the DataInfo moments in as constants;
            # only beta (and the offset scalar) ride as arguments
            self._dev = (jnp.asarray(a["beta"].astype(np.float32)),)
            return self._dev
        F = len(self.names)
        lens = [int(v) for v in a["spec_edges_len"].reshape(-1)]
        emax = max(lens, default=0) or 1
        ep = np.full((F, emax), np.inf, np.float32)
        flat, pos = a["spec_edges_flat"], 0
        for i, ln in enumerate(lens):
            ep[i, :ln] = np.asarray(flat[pos: pos + ln], np.float32)
            pos += ln
        init = (np.asarray(a["init_class"], np.float32)
                if "init_class" in a
                else np.float32(self.manifest["init_f"]))
        self._dev = (jnp.asarray(ep), jnp.asarray(self.is_cat),
                     jnp.asarray(init),
                     jnp.asarray(a["feat"]), jnp.asarray(a["thresh_bin"]),
                     jnp.asarray(a["na_left"].astype(bool)),
                     jnp.asarray(a["left"]), jnp.asarray(a["right"]),
                     jnp.asarray(a["leaf_val"].astype(np.float32)),
                     jnp.asarray(a["cat_split"]),
                     jnp.asarray(a["cat_table"].astype(bool)),
                     jnp.asarray(a["tree_class"]),
                     jnp.asarray(a["na_bins"]))
        return self._dev

    # -- executables ------------------------------------------------------
    def _executable(self, bucket: int):
        exe = self._exec.get(bucket)
        if exe is not None:
            return exe
        m = self.manifest
        fp = _backend_fingerprint()
        for e in m.get("executables", []):
            if int(e.get("bucket", -1)) != bucket or e.get("backend") != fp:
                continue
            blob = _read_payload(self.dir, e)
            try:
                d = _ExecBlobUnpickler(io.BytesIO(blob)).load()
                if not isinstance(d, dict) or d.get("v") != _BLOB_VERSION:
                    raise ArtifactError("unsupported executable blob "
                                        "version")
                from jax.experimental import serialize_executable as se

                loaded = se.deserialize_and_load(d["payload"], d["in_tree"],
                                                 d["out_tree"])
            except pickle.UnpicklingError:
                raise            # tampered blob: refuse, never fall back
            except Exception:    # noqa: BLE001 — backend can't load: HLO
                break
            self._exec[bucket] = ("loaded", loaded)
            self.loaded_from[bucket] = "exec"
            return self._exec[bucket]
        for e in m.get("stablehlo", []):
            if int(e.get("bucket", -1)) != bucket:
                continue
            kept = e.get("kept_args")
            if kept is None:
                raise ArtifactError(
                    f"bucket {bucket}: no loadable executable for this "
                    "backend and the StableHLO entry carries no argument "
                    "mapping — re-export the artifact on a current "
                    "framework build")
            import jax

            text = _read_payload(self.dir, e).decode("utf-8")
            raw = jax.devices()[0].client.compile(text)
            self._exec[bucket] = ("raw", raw, [int(i) for i in kept])
            self.loaded_from[bucket] = "hlo"
            return self._exec[bucket]
        raise ArtifactError(f"artifact has no program for bucket {bucket}")

    def _split_glm_cols(self, X_pad: np.ndarray) -> List[np.ndarray]:
        """(bucket, P) matrix → the per-column argument list the GLM
        program was lowered with: int32 categorical codes (NaN/negative →
        -1, which the program's mode imputation sees as NA — the same
        value adapt_test's unseen-level remap produces), then float32
        numerics."""
        ncat = int(self.glm["n_cat"])
        cols: List[np.ndarray] = []
        for i in range(ncat):
            c = X_pad[:, i]
            cols.append(np.where(np.isnan(c), -1.0, c).astype(np.int32))
        for j in range(int(self.glm["n_num"])):
            cols.append(np.ascontiguousarray(X_pad[:, ncat + j],
                                             np.float32))
        return cols

    def _run_dev(self, bucket: int, X_pad: np.ndarray):
        """Dispatch one bucket; returns the program output WITHOUT forcing
        a host transfer (the serving-QPS path keeps it device-resident
        through post-processing and fetches once)."""
        import jax.numpy as jnp

        got = self._executable(bucket)
        if self.model_type == "pipeline":
            # one program: raw (bucket, R) matrix in, margins/mu out.
            # The offset scalar rides as the second argument exactly like
            # the glm lowering (kept-args filtering prunes it for forest
            # cores).
            if got[0] == "loaded":
                return got[1](X_pad, 0.0)
            _kind, exe, kept = got
            flat = [jnp.asarray(X_pad), jnp.float32(0.0)]
            outs = exe.execute([flat[i] for i in kept])
            return outs[0]
        if self.model_type == "glm":
            cols = self._split_glm_cols(X_pad)
            (beta,) = self._device_args()
            if got[0] == "loaded":
                # the lowered pytree: (cols_tuple, beta, offset) — offset
                # is the same concrete 0.0 _predict_raw passes. Host
                # arrays go in as-is: the loaded executable's C++ call
                # path device-puts them faster than an explicit asarray.
                return got[1](tuple(cols), beta, 0.0)
            _kind, exe, kept = got
            flat = [jnp.asarray(c) for c in cols] + [beta,
                                                     jnp.float32(0.0)]
            outs = exe.execute([flat[i] for i in kept])
            return outs[0]
        if got[0] == "loaded":
            # numpy straight in — the executable's own transfer path is
            # measurably cheaper than jnp.asarray + call
            return got[1](X_pad, *self._device_args())
        args = (jnp.asarray(X_pad),) + self._device_args()
        _kind, exe, kept = got
        # jit pruned unused Python-level args from the XLA signature; the
        # raw-client execute path must bind only the kept ones, in order
        outs = exe.execute([args[i] for i in kept])
        return outs[0]

    def _run(self, bucket: int, X_pad: np.ndarray) -> np.ndarray:
        return np.asarray(self._run_dev(bucket, X_pad))

    # -- feature packing --------------------------------------------------
    def pack_features(self, cols: Dict[str, Any]) -> np.ndarray:
        """(n, F) float32 matrix in training-column order: numerics as
        floats (unparseable/missing -> NaN), categoricals as training-
        domain codes (unseen/missing -> -1, which bins to the NA bin) —
        the same convention ScoringSession._features feeds the program."""
        n = 0
        for v in cols.values():
            n = max(n, len(np.asarray(v, dtype=object).reshape(-1)))
        X = np.empty((n, len(self.names)), np.float32)
        for i, name in enumerate(self.names):
            dom = self.domains.get(name)
            raw = cols.get(name)
            if raw is None:
                X[:, i] = -1.0 if dom is not None else np.nan
                continue
            vals = np.asarray(raw, dtype=object).reshape(-1)
            if dom is not None:
                lut = {str(lvl): k for k, lvl in enumerate(dom)}
                X[:, i] = [lut.get(str(v).strip(), -1)
                           if v is not None and str(v).strip() != ""
                           else -1 for v in vals]
            else:
                def as_float(v):
                    try:
                        return float(v)
                    except (TypeError, ValueError):
                        return np.nan
                X[:, i] = [as_float(v) for v in vals]
        return X

    # -- scoring ----------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def margins(self, X: np.ndarray) -> np.ndarray:
        """(n,) or (n, K) float32 margins — bitwise-identical to the
        server's fused bucketed program (it IS the server's program)."""
        X = np.asarray(X, np.float32)
        n = X.shape[0]
        maxb = self.buckets[-1]
        outs: List[np.ndarray] = []
        pos = 0
        while pos < n:
            chunk = X[pos: pos + maxb]
            m = chunk.shape[0]
            bucket = self._bucket_for(m)
            buf = np.zeros((bucket, X.shape[1]), np.float32)
            buf[:m] = chunk
            outs.append(self._run(bucket, buf)[:m])
            pos += m
        if not outs:
            K = (self.nclasses
                 if (self.nclasses > 2 or self.per_class) else 1)
            return np.zeros((0,) if K == 1 else (0, K), np.float32)
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def _out_key(self) -> str:
        return "probs" if self.post.get("kind") in (
            "binomial", "multinomial", "glm_binomial",
            "glm_multinomial") else "value"

    def _post(self, f_dev):
        """Post-processing (margins → probs/value) as ONE cached jit
        program over the device-resident margins — the identical jnp ops
        the server runs in _margin_to_raw, fused so a request pays a
        single extra dispatch instead of one per eager op."""
        fn = self._post_jit
        if fn is None:
            import jax
            import jax.numpy as jnp

            kind = self.post.get("kind")
            exp_link = self.post.get("linkinv") == "exp"
            if kind in ("binomial", "glm_binomial"):
                def post(f):
                    p = 1.0 / (1.0 + jnp.exp(-f)) if kind == "binomial" \
                        else f        # glm program already applied linkinv
                    return jnp.stack([1 - p, p], axis=-1)
            elif kind == "multinomial":
                def post(f):
                    return jax.nn.softmax(f, axis=-1)
            elif kind == "glm_multinomial":
                def post(f):          # probs computed inside the program
                    return f
            elif exp_link:
                def post(f):
                    return jnp.exp(f)
            else:
                def post(f):
                    return f

            fn = self._post_jit = jax.jit(post)
        return fn(f_dev)

    def raw_predict(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        """Margins + post-processing with the identical jnp ops the server
        runs in SharedTreeModel._margin_to_raw / GLM's linkinv — computed
        as one device-resident pipeline per bucket chunk (program dispatch
        → fused post program → ONE host fetch). This is the sustained-QPS
        path: no intermediate host round-trip, no per-eager-op dispatch,
        and an exactly-bucket-sized batch skips the pad copy."""
        X = np.asarray(X, np.float32)
        n = X.shape[0]
        maxb = self.buckets[-1]
        outs: List[np.ndarray] = []
        pos = 0
        while pos < n:
            chunk = X[pos: pos + maxb]
            m = chunk.shape[0]
            bucket = self._bucket_for(m)
            if m == bucket:
                buf = np.ascontiguousarray(chunk, np.float32)
            else:
                buf = np.zeros((bucket, X.shape[1]), np.float32)
                buf[:m] = chunk
            out = self._post(self._run_dev(bucket, buf))
            outs.append(np.asarray(out)[:m])
            pos += m
        if not outs:
            K = (self.nclasses
                 if (self.nclasses > 2 or self.per_class) else 1)
            if self._out_key() == "probs":
                width = self.nclasses if self.nclasses > 2 else 2
                return {"probs": np.zeros((0, width), np.float32)}
            return {"value": np.zeros((0,) if K == 1 else (0, K),
                                      np.float32)}
        res = outs[0] if len(outs) == 1 else np.concatenate(outs)
        return {self._out_key(): res}

    def raw_from_margins(self, margins: np.ndarray
                         ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        return {self._out_key():
                np.asarray(self._post(jnp.asarray(margins)))}

    def score(self, cols: Dict[str, Any],
              raw: Dict[str, np.ndarray] = None) -> Dict[str, np.ndarray]:
        """Batch scoring: raw columns -> the server predict-frame shape
        (predict + per-class probability columns). Pass `raw` to label a
        result already computed via raw_predict/raw_from_margins instead
        of scoring the columns again."""
        if raw is None:
            raw = self.raw_predict(self.pack_features(cols))
        out: Dict[str, np.ndarray] = {}
        if "probs" in raw:
            probs = np.asarray(raw["probs"])
            dom = self.response_domain or [str(i)
                                           for i in range(probs.shape[1])]
            if self.category == "Binomial":
                label = (probs[:, 1] >= self.default_threshold).astype(int)
            else:
                label = probs.argmax(axis=-1)
            out["predict"] = np.asarray([dom[i] for i in label], object)
            for k, lvl in enumerate(dom):
                out[str(lvl)] = probs[:, k]
        else:
            out["predict"] = np.asarray(raw["value"])
        return out


def load_artifact(art_dir: str) -> AotScorer:
    """Load an AOT artifact directory into a standalone scorer."""
    return AotScorer(art_dir)
