"""h2o3_genmodel — standalone MOJO scoring runtime.

The dependency-free counterpart of the reference's h2o-genmodel jar
(h2o-genmodel/src/main/java/hex/genmodel/easy/EasyPredictModelWrapper.java:1,
MojoModel.java:1): loads a MOJO zip exported by h2o3_tpu and scores rows
using ONLY numpy + the standard library — no h2o3_tpu, no jax, no server.

Usage:
    import h2o3_genmodel as gm
    model = gm.load_mojo("model.zip")
    res = model.predict({"x1": 0.3, "g": "b"})       # one row, EasyPredict
    tbl = model.score(cols)                          # batch: dict of arrays

CLI (hex/genmodel/tools/PredictCsv.java analog):
    python -m h2o3_genmodel.predict_csv --mojo model.zip \
        --input in.csv --output out.csv

AOT artifacts (the serving-tier lineage; needs jax at score time):
    scorer = gm.load_artifact("model_artifact/")   # AOT executable + HLO
    tbl = scorer.score(cols)
    python -m h2o3_genmodel.aot_predict --artifact model_artifact/ \
        --input in.csv --output out.csv
"""

from h2o3_genmodel.aot import AotScorer, load_artifact
from h2o3_genmodel.easy import (AnomalyPrediction, BinomialPrediction,
                                ClusteringPrediction, EasyPredictor,
                                MultinomialPrediction, RegressionPrediction,
                                load_mojo)

__version__ = "1.0.0"
__all__ = ["load_mojo", "EasyPredictor", "BinomialPrediction",
           "MultinomialPrediction", "RegressionPrediction",
           "ClusteringPrediction", "AnomalyPrediction",
           "load_artifact", "AotScorer", "__version__"]
