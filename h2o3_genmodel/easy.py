"""EasyPredictModelWrapper analog — typed row predictions over a MOJO.

Reference: hex/genmodel/easy/EasyPredictModelWrapper.java:1 and the
prediction POJOs under hex/genmodel/easy/prediction/."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_genmodel.reader import read_mojo_bundle
from h2o3_genmodel.scorers import ColumnBlock, build_scorer


@dataclass
class BinomialPrediction:
    label: str
    class_probabilities: List[float]


@dataclass
class MultinomialPrediction:
    label: str
    class_probabilities: List[float]


@dataclass
class RegressionPrediction:
    value: float


@dataclass
class ClusteringPrediction:
    cluster: int
    distances: List[float] = field(default_factory=list)


@dataclass
class AnomalyPrediction:
    score: float
    normalized_score: float


class EasyPredictor:
    """Loads a MOJO once; predicts single rows (dicts) or batches (dict of
    columns). Mirrors EasyPredictModelWrapper's categorical handling: unseen
    levels and missing columns score as NA."""

    def __init__(self, bundle):
        self.bundle = bundle
        s = bundle.scorer
        self.algo: str = s["algo"]
        self.category: str = s["model_category"]
        self.names: List[str] = list(s["names"])
        self.response_domain: List[str] = list(s.get("response_domain") or [])
        self.default_threshold = float(s.get("default_threshold", 0.5))
        self._scorer = build_scorer(bundle)

    # -- batch ------------------------------------------------------------
    def score(self, cols: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Batch scoring: dict of raw columns → output columns, the same
        table shape as the server's predict frame (predict + per-class
        probability columns named by response level)."""
        block = ColumnBlock.from_dict(cols)
        raw = self._scorer.raw_predict(block)
        out: Dict[str, np.ndarray] = {}
        if "probs" in raw:
            probs = np.asarray(raw["probs"])
            dom = self.response_domain or [str(i) for i in
                                           range(probs.shape[1])]
            if self.category == "Binomial":
                label = (probs[:, 1] >= self.default_threshold).astype(int)
            else:
                label = probs.argmax(axis=-1)
            out["predict"] = np.asarray([dom[i] for i in label], object)
            for k, lvl in enumerate(dom):
                out[str(lvl)] = probs[:, k]
        elif "cluster" in raw:
            out["predict"] = np.asarray(raw["cluster"], np.int64)
        elif "score" in raw and self.category == "AnomalyDetection":
            out["predict"] = np.asarray(raw["score"])
            if "mean_length" in raw:
                out["mean_length"] = np.asarray(raw["mean_length"])
        elif "scores" in raw:        # PCA: PC1..PCk (DimReduction table)
            scores = np.asarray(raw["scores"])
            for j in range(scores.shape[1]):
                out[f"PC{j+1}"] = scores[:, j]
        elif "te" in raw:            # TargetEncoder: <col>_te columns
            for name, arr in raw["te"].items():
                out[name] = np.asarray(arr)
        elif "vectors" in raw:       # Word2Vec: embedding columns V1..Vd
            vecs = np.asarray(raw["vectors"])
            for j in range(vecs.shape[1]):
                out[f"V{j+1}"] = vecs[:, j]
        else:
            out["predict"] = np.asarray(raw["value"])
        return out

    # -- single row (EasyPredictModelWrapper.predict*) --------------------
    def predict(self, row: Dict[str, Any]):
        cols = {k: [v] for k, v in row.items()}
        block = ColumnBlock.from_dict(cols)
        raw = self._scorer.raw_predict(block)
        if self.category == "Binomial":
            p = np.asarray(raw["probs"])[0]
            label = self.response_domain[int(p[1] >= self.default_threshold)]
            return BinomialPrediction(label, [float(x) for x in p])
        if self.category == "Multinomial":
            p = np.asarray(raw["probs"])[0]
            dom = self.response_domain or [str(i) for i in range(len(p))]
            return MultinomialPrediction(dom[int(p.argmax())],
                                         [float(x) for x in p])
        if self.category == "Clustering":
            return ClusteringPrediction(int(raw["cluster"][0]))
        if self.category == "AnomalyDetection":
            # reference AnomalyDetectionPrediction: score = mean path
            # length, normalizedScore = the [0,1] 2^(-len/c) value
            ml = raw.get("mean_length")
            norm = float(raw["score"][0])
            return AnomalyPrediction(
                float(ml[0]) if ml is not None else norm, norm)
        return RegressionPrediction(float(np.asarray(raw["value"])[0]))


def load_mojo(source) -> EasyPredictor:
    """Load a MOJO zip (path / bytes / file-like) into a predictor."""
    return EasyPredictor(read_mojo_bundle(source))
