# Connection + wire layer. Mirrors the reference client's REST contract
# (h2o-r/h2o-package/R/connection.R + communication.R: urlencoded POST
# bodies, /3/Cloud boot probe, /3/InitID session) over the system curl
# binary, so the package needs no compiled dependencies.

.h2o.env <- new.env(parent = emptyenv())

.h2o.base <- function() {
  b <- get0("base_url", envir = .h2o.env)
  if (is.null(b)) stop("no active connection; call h2o.init() first")
  b
}

.h2o.esc <- function(x) {
  # communication.R curlEscape on every value
  vapply(as.character(x), utils::URLencode, "", reserved = TRUE,
         USE.NAMES = FALSE)
}

.h2o.curl <- function(args) {
  out <- suppressWarnings(system2("curl", c("-s", "-S", args),
                                  stdout = TRUE, stderr = TRUE))
  status <- attr(out, "status")
  if (!is.null(status) && status != 0)
    stop("curl failed (", status, "): ", paste(out, collapse = "\n"))
  paste(out, collapse = "\n")
}

.h2o.fromJSON <- function(txt) {
  res <- jsonlite::fromJSON(txt, simplifyVector = FALSE)
  # H2O error schema: surface exception_msg/msg like .h2o.doSafeREST
  if (!is.null(res$exception_msg)) stop(res$exception_msg)
  if (!is.null(res$error_url) && !is.null(res$msg)) stop(res$msg)
  res
}

.h2o.GET <- function(path, params = list()) {
  url <- paste0(.h2o.base(), path)
  if (length(params)) {
    q <- paste(names(params), .h2o.esc(unlist(params)),
               sep = "=", collapse = "&")
    url <- paste0(url, "?", q)
  }
  .h2o.fromJSON(.h2o.curl(url))
}

.h2o.POST <- function(path, params = list()) {
  # curlPerform(postfields = name=value&...) — NEVER json (communication.R)
  body <- if (length(params)) {
    paste(names(params), .h2o.esc(unlist(params)), sep = "=", collapse = "&")
  } else ""
  .h2o.fromJSON(.h2o.curl(c("-X", "POST",
                            "-H", "Content-Type: application/x-www-form-urlencoded",
                            "--data", body, paste0(.h2o.base(), path))))
}

.h2o.DELETE <- function(path) {
  .h2o.fromJSON(.h2o.curl(c("-X", "DELETE", paste0(.h2o.base(), path))))
}

# connection.R h2o.init: probe /3/Cloud until healthy, open an /3/InitID
# session key for Rapids scoping
h2o.init <- function(ip = "localhost", port = 54321, https = FALSE,
                     max_retries = 20) {
  scheme <- if (https) "https" else "http"
  assign("base_url", sprintf("%s://%s:%d", scheme, ip, port),
         envir = .h2o.env)
  for (i in seq_len(max_retries)) {
    cloud <- tryCatch(.h2o.GET("/3/Cloud"), error = function(e) NULL)
    if (!is.null(cloud) && isTRUE(cloud$cloud_healthy)) {
      sess <- .h2o.POST("/3/InitID")
      assign("session_id", sess$session_key, envir = .h2o.env)
      message(sprintf("Connected to h2o3-tpu cloud '%s' (%d device(s))",
                      cloud$cloud_name, cloud$cloud_size))
      return(invisible(cloud))
    }
    Sys.sleep(0.5)
  }
  stop("could not connect to ", .h2o.base())
}

h2o.clusterInfo <- function() .h2o.GET("/3/Cloud")

h2o.shutdown <- function(prompt = FALSE) {
  if (prompt) {
    ans <- readline("Are you sure you want to shutdown the cloud? (Y/N) ")
    if (!identical(toupper(ans), "Y")) return(invisible(FALSE))
  }
  invisible(tryCatch(.h2o.POST("/3/Shutdown"), error = function(e) NULL))
}

# models.R .h2o.getFutureModel-style job poll
.h2o.waitJob <- function(job_key, poll_s = 0.2, timeout_s = 3600) {
  deadline <- Sys.time() + timeout_s
  path <- paste0("/3/Jobs/", .h2o.esc(job_key))
  while (Sys.time() < deadline) {
    j <- .h2o.GET(path)$jobs[[1]]
    if (j$status %in% c("DONE")) return(invisible(j))
    if (j$status %in% c("FAILED", "CANCELLED"))
      stop("job ", job_key, " ", j$status, ": ",
           if (!is.null(j$exception)) j$exception else "")
    Sys.sleep(poll_s)
  }
  stop("job ", job_key, " timed out")
}
