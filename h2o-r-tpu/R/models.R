# Model training + scoring. Mirrors h2o-r/h2o-package/R/models.R:
# .h2o.startModelJob posts urlencoded params to /3/ModelBuilders/{algo},
# predict posts to /4/Predictions and reads key/dest at the TOP level of
# the v4 response (models.R:679 res$key$name, res$dest$name).

.h2o.frameId <- function(fr) {
  if (inherits(fr, "H2OFrame")) fr$frame_id else as.character(fr)
}

.h2o.trainModel <- function(algo, x, y, training_frame,
                            validation_frame = NULL, model_id = NULL, ...) {
  params <- list(training_frame = .h2o.frameId(training_frame))
  if (!is.null(y)) params$response_column <- y
  if (!is.null(validation_frame))
    params$validation_frame <- .h2o.frameId(validation_frame)
  if (!is.null(model_id)) params$model_id <- model_id
  extra <- list(...)
  for (k in names(extra)) {
    v <- extra[[k]]
    if (is.null(v)) next
    # models.R: R logicals go as TRUE/FALSE words, vectors as [a,b,c]
    params[[k]] <- if (is.logical(v)) {
      if (v) "TRUE" else "FALSE"
    } else if (length(v) > 1) {
      paste0("[", paste(v, collapse = ","), "]")
    } else v
  }
  if (!is.null(x)) {
    keep <- unique(c(x, y))
    fg <- .h2o.GET(paste0("/3/Frames/",
                          .h2o.esc(params$training_frame)),
                   list(row_count = 1))$frames[[1]]
    all_cols <- vapply(fg$columns, function(c) c$label, "")
    ign <- setdiff(all_cols, keep)
    if (length(ign))
      params$ignored_columns <- paste0("[", paste0("\"", ign, "\"",
                                                   collapse = ","), "]")
  }
  res <- .h2o.POST(paste0("/3/ModelBuilders/", algo), params)
  job <- .h2o.waitJob(res$job$key$name)
  h2o.getModel(job$dest$name)
}

h2o.getModel <- function(model_id) {
  m <- .h2o.GET(paste0("/3/Models/", .h2o.esc(model_id)))$models[[1]]
  structure(list(model_id = model_id, algo = m$algo, model = m),
            class = "H2OModel")
}

print.H2OModel <- function(x, ...) {
  cat(sprintf("H2OModel '%s' (%s)\n", x$model_id, x$algo))
  invisible(x)
}

h2o.gbm <- function(x = NULL, y, training_frame, validation_frame = NULL,
                    model_id = NULL, ...)
  .h2o.trainModel("gbm", x, y, training_frame, validation_frame,
                  model_id, ...)

h2o.glm <- function(x = NULL, y, training_frame, validation_frame = NULL,
                    model_id = NULL, ...)
  .h2o.trainModel("glm", x, y, training_frame, validation_frame,
                  model_id, ...)

h2o.randomForest <- function(x = NULL, y, training_frame,
                             validation_frame = NULL, model_id = NULL, ...)
  .h2o.trainModel("drf", x, y, training_frame, validation_frame,
                  model_id, ...)

h2o.deeplearning <- function(x = NULL, y, training_frame,
                             validation_frame = NULL, model_id = NULL, ...)
  .h2o.trainModel("deeplearning", x, y, training_frame, validation_frame,
                  model_id, ...)

# automl.R h2o.automl: JSON body on /99/AutoMLBuilder (the one jsonized
# request in the reference client too)
h2o.automl <- function(x = NULL, y, training_frame, max_models = 10,
                       project_name = NULL, nfolds = -1, seed = NULL, ...) {
  spec <- list(
    input_spec = list(training_frame = .h2o.frameId(training_frame),
                      response_column = y),
    build_control = list(
      stopping_criteria = list(max_models = max_models)))
  if (!is.null(project_name)) spec$build_control$project_name <- project_name
  if (nfolds >= 0) spec$build_control$nfolds <- nfolds
  if (!is.null(seed)) spec$build_control$stopping_criteria$seed <- seed
  body <- jsonlite::toJSON(spec, auto_unbox = TRUE)
  tmp <- tempfile(); on.exit(unlink(tmp))
  writeLines(body, tmp)
  res <- .h2o.fromJSON(.h2o.curl(c(
    "-X", "POST", "-H", "Content-Type: application/json",
    "--data", paste0("@", tmp),
    paste0(.h2o.base(), "/99/AutoMLBuilder"))))
  .h2o.waitJob(res$job$key$name)
  project <- res$build_control$project_name
  lb <- .h2o.GET(paste0("/99/Leaderboards/", .h2o.esc(project)))
  list(project_name = project, leaderboard = lb)
}

# models.R predict.H2OModel/h2o.predict: async v4 route, dest at top level
h2o.predict <- function(object, newdata, ...) {
  res <- .h2o.POST(paste0("/4/Predictions/models/",
                          .h2o.esc(object$model_id), "/frames/",
                          .h2o.esc(.h2o.frameId(newdata))))
  dest <- if (!is.null(res$dest)) res$dest$name else res$key$name
  if (!is.null(res$job)) .h2o.waitJob(res$job$key$name)
  .h2o.newFrame(dest)
}

predict.H2OModel <- function(object, newdata, ...)
  h2o.predict(object, newdata, ...)

# models.R h2o.performance: the synchronous v3 metrics route
h2o.performance <- function(model, newdata) {
  res <- .h2o.POST(paste0("/3/Predictions/models/",
                          .h2o.esc(model$model_id), "/frames/",
                          .h2o.esc(.h2o.frameId(newdata))))
  if (length(res$model_metrics)) res$model_metrics[[1]] else NULL
}
