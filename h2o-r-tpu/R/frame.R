# Frames. Mirrors h2o-r/h2o-package/R/frame.R + parse.R surface: an
# H2OFrame is a lightweight handle (frame_id + cached dims); data pulls
# ride /3/DownloadDataset as CSV.

.h2o.newFrame <- function(frame_id) {
  fg <- .h2o.GET(paste0("/3/Frames/", .h2o.esc(frame_id)),
                 list(row_count = 1))$frames[[1]]
  structure(list(frame_id = frame_id,
                 nrows = fg$rows,
                 ncols = length(fg$columns),
                 col_names = vapply(fg$columns, function(c) c$label, "")),
            class = "H2OFrame")
}

# parse.R h2o.importFile -> h2o.parseRaw: POST /3/Parse with the R-style
# ["path"] source_frames list (.collapse.char), then poll the parse job
h2o.importFile <- function(path, destination_frame = NULL, header = NA,
                           col.names = NULL) {
  if (is.null(destination_frame) || !nzchar(destination_frame)) {
    base <- sub("\\.[^.]*$", "", basename(path))
    destination_frame <- paste0(base, ".hex")
  }
  params <- list(
    source_frames = paste0("[\"", path, "\"]"),
    destination_frame = destination_frame)
  if (!is.na(header)) params$header <- if (isTRUE(header)) 1 else 0
  if (!is.null(col.names))
    params$column_names <- paste0("[", paste0("\"", col.names, "\"",
                                              collapse = ","), "]")
  res <- .h2o.POST("/3/Parse", params)
  .h2o.waitJob(res$job$key$name)
  .h2o.newFrame(destination_frame)
}

h2o.getFrame <- function(id) .h2o.newFrame(id)

h2o.ls <- function() {
  fr <- .h2o.GET("/3/Frames")$frames
  ml <- .h2o.GET("/3/Models")$models
  data.frame(key = c(vapply(fr, function(f) f$frame_id$name, ""),
                     vapply(ml, function(m) m$model_id$name, "")),
             type = c(rep("frame", length(fr)), rep("model", length(ml))),
             stringsAsFactors = FALSE)
}

h2o.rm <- function(id) {
  id <- if (inherits(id, "H2OFrame")) id$frame_id else as.character(id)
  invisible(.h2o.DELETE(paste0("/3/Frames/", .h2o.esc(id))))
}

dim.H2OFrame <- function(x) c(x$nrows, x$ncols)

print.H2OFrame <- function(x, ...) {
  cat(sprintf("H2OFrame '%s': %d rows x %d cols\n",
              x$frame_id, x$nrows, x$ncols))
  invisible(x)
}

# frame.R as.data.frame.H2OFrame: stream the frame back as CSV
# (/3/DownloadDataset, the same route the reference client uses)
as.data.frame.H2OFrame <- function(x, ...) {
  url <- paste0(.h2o.base(), "/3/DownloadDataset?frame_id=",
                .h2o.esc(x$frame_id))
  tmp <- tempfile(fileext = ".csv")
  on.exit(unlink(tmp))
  .h2o.curl(c("-o", tmp, url))
  utils::read.csv(tmp, stringsAsFactors = FALSE)
}
