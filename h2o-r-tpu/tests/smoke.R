# End-to-end smoke: init -> importFile -> gbm -> predict -> as.data.frame.
# Run with: Rscript smoke.R <port> <csv_path>
# (tests/test_h2or_client.py launches this against a live server when an
# R runtime exists; the same wire sequence is replayed in python otherwise)

args <- commandArgs(trailingOnly = TRUE)
port <- as.integer(args[[1]])
csv <- args[[2]]

pkg_dir <- file.path(dirname(sub("--file=", "",
  grep("--file=", commandArgs(), value = TRUE))), "..", "R")
for (f in list.files(pkg_dir, full.names = TRUE)) source(f)

h2o.init(port = port)
fr <- h2o.importFile(csv, destination_frame = "r_smoke.hex")
stopifnot(dim(fr)[1] > 0)
cat("IMPORT_OK", dim(fr)[1], dim(fr)[2], "\n")

m <- h2o.gbm(y = "y", training_frame = fr, ntrees = 3, max_depth = 3,
             model_id = "r_smoke_gbm")
cat("TRAIN_OK", m$model_id, "\n")

p <- h2o.predict(m, fr)
df <- as.data.frame(p)
stopifnot(nrow(df) == dim(fr)[1], "predict" %in% names(df))
cat("PREDICT_OK", nrow(df), "\n")

perf <- h2o.performance(m, fr)
cat("PERF_OK", if (!is.null(perf$AUC)) perf$AUC else "NA", "\n")
cat("R_SMOKE_DONE\n")
